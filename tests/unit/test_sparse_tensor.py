"""Sparse-gradient tests (reference ``tests/unit/runtime/test_sparse_grads``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.sparse_tensor import (SparseTensor,
                                                 sparse_allreduce,
                                                 sparse_allreduce_dense_result)


def _rowsparse(v=64, d=8, rows=(3, 10, 41), seed=0):
    rng = np.random.default_rng(seed)
    dense = np.zeros((v, d), np.float32)
    for r in rows:
        dense[r] = rng.normal(size=d)
    return jnp.asarray(dense)


def test_from_dense_roundtrip():
    dense = _rowsparse()
    st = SparseTensor.from_dense(dense)
    assert st.nnz == 4  # 3 rows -> power-of-two budget 4
    np.testing.assert_allclose(np.asarray(st.to_dense()), np.asarray(dense),
                               atol=1e-7)


def test_duplicate_indices_accumulate():
    st = SparseTensor(jnp.asarray([2, 2, 5], jnp.int32),
                      jnp.ones((3, 4), jnp.float32), (8, 4))
    dense = np.asarray(st.to_dense())
    assert (dense[2] == 2.0).all() and (dense[5] == 1.0).all()
    assert dense.sum() == 3 * 4


def test_static_budget_truncates_smallest():
    dense = _rowsparse(rows=(1, 2, 3, 4))
    st = SparseTensor.from_dense(dense, k=2)
    assert st.nnz == 2
    kept = np.asarray(st.to_dense())
    # the two largest-norm rows survive
    norms = np.abs(np.asarray(dense)).sum(-1)
    top2 = set(np.argsort(norms)[-2:])
    nz = {i for i in range(dense.shape[0]) if np.abs(kept[i]).sum() > 0}
    assert nz == top2


def test_sparse_allreduce_matches_dense_psum(eight_devices):
    """Sparse all-gather+densify == dense psum mean over the dp axis."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.topology import MeshTopology

    mesh = MeshTopology(dp=8).mesh
    per_rank = [np.asarray(_rowsparse(rows=(r, (r * 3) % 64), seed=r))
                for r in range(8)]
    stacked = jnp.asarray(np.stack(per_rank))          # [8, V, D]
    expected = np.mean(np.stack(per_rank), axis=0)

    @jax.jit
    def run(x):
        def body(xw):
            st = SparseTensor.from_dense(xw[0], k=4)
            return sparse_allreduce_dense_result(st, "dp")[None]

        return shard_map(body, mesh=mesh, in_specs=P("dp"),
                         out_specs=P("dp"))(x)

    with mesh:
        out = np.asarray(run(stacked))
    for r in range(8):  # every rank holds the same reduced dense tensor
        np.testing.assert_allclose(out[r], expected, atol=1e-6)


def test_sparse_allreduce_sum_mode(eight_devices):
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.topology import MeshTopology

    mesh = MeshTopology(dp=8).mesh
    x = jnp.asarray(np.stack([np.asarray(_rowsparse(rows=(5,), seed=0))
                              for _ in range(8)]))

    @jax.jit
    def run(x):
        def body(xw):
            st = SparseTensor.from_dense(xw[0], k=1)
            return sparse_allreduce(st, "dp", average=False).to_dense()[None]

        return shard_map(body, mesh=mesh, in_specs=P("dp"),
                         out_specs=P("dp"))(x)

    with mesh:
        out = np.asarray(run(x))
    np.testing.assert_allclose(out[0][5], 8 * np.asarray(x)[0][5], atol=1e-5)
