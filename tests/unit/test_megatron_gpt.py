"""Megatron-GPT ingestion tests: fabricate Megatron-format TP shards (both
qkv layouts) from a reference HF GPT-2 and check the merged model matches
(reference MegatronSDLoader semantics, ``state_dict_factory.py:214``)."""

import numpy as np
import pytest

from deepspeed_tpu.models import gpt2, megatron_gpt

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

H, L, NH, V, S = 32, 2, 4, 96, 64
HN = H // NH


def _tiny_hf():
    cfg = transformers.GPT2Config(vocab_size=V, n_positions=S, n_embd=H,
                                  n_layer=L, n_head=NH, attn_pdrop=0.0,
                                  embd_pdrop=0.0, resid_pdrop=0.0)
    with torch.no_grad():
        m = transformers.GPT2LMHeadModel(cfg)
    m.eval()
    return m


def _v0_rows(w_conv1d):
    """HF Conv1D [in, 3h] -> Megatron version-0 rows [3h, in] (q|k|v)."""
    return np.asarray(w_conv1d).T


def _v2_rows(v0):
    """version 0 (3, n, hn) rows -> version 2.0 (n, 3, hn) rows."""
    h = v0.shape[1]
    return v0.reshape(3, NH, HN, h).transpose(1, 0, 2, 3).reshape(3 * H, h)


def _v2_bias(v0):
    return v0.reshape(3, NH, HN).transpose(1, 0, 2).reshape(3 * H)


def _megatron_shards(hf, tp=2, version=2.0):
    """Split the HF model into `tp` Megatron-format rank state dicts."""
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    heads_per = NH // tp
    shards = []
    for r in range(tp):
        out = {}
        out["word_embeddings.weight"] = np.split(
            sd["transformer.wte.weight"], tp, axis=0)[r]
        out["position_embeddings.weight"] = sd["transformer.wpe.weight"]
        for i in range(L):
            p = f"transformer.layers.{i}."
            hfp = f"transformer.h.{i}."
            out[p + "input_layernorm.weight"] = sd[hfp + "ln_1.weight"]
            out[p + "input_layernorm.bias"] = sd[hfp + "ln_1.bias"]
            v0w = _v0_rows(sd[hfp + "attn.c_attn.weight"])
            v0b = sd[hfp + "attn.c_attn.bias"]
            if version == 0:
                # q|k|v rows; column-parallel shard = per-projection slice
                qs, ks, vs = np.split(v0w, 3, axis=0)
                qb, kb, vb = np.split(v0b, 3)
                sl = slice(r * heads_per * HN, (r + 1) * heads_per * HN)
                out[p + "attention.query_key_value.weight"] = np.concatenate(
                    [qs[sl], ks[sl], vs[sl]], axis=0)
                out[p + "attention.query_key_value.bias"] = np.concatenate(
                    [qb[sl], kb[sl], vb[sl]])
            else:
                rows = _v2_rows(v0w)
                brows = _v2_bias(v0b)
                per = 3 * HN * heads_per
                out[p + "attention.query_key_value.weight"] = \
                    rows[r * per:(r + 1) * per]
                out[p + "attention.query_key_value.bias"] = \
                    brows[r * per:(r + 1) * per]
            # row-parallel: torch [out, in] splits input columns
            o_w = sd[hfp + "attn.c_proj.weight"].T      # [H, H] torch layout
            out[p + "attention.dense.weight"] = np.split(o_w, tp, axis=1)[r]
            out[p + "attention.dense.bias"] = sd[hfp + "attn.c_proj.bias"]
            out[p + "post_attention_layernorm.weight"] = sd[hfp + "ln_2.weight"]
            out[p + "post_attention_layernorm.bias"] = sd[hfp + "ln_2.bias"]
            fc = sd[hfp + "mlp.c_fc.weight"].T          # [4H, H]
            out[p + "mlp.dense_h_to_4h.weight"] = np.split(fc, tp, axis=0)[r]
            out[p + "mlp.dense_h_to_4h.bias"] = np.split(
                sd[hfp + "mlp.c_fc.bias"], tp)[r]
            pj = sd[hfp + "mlp.c_proj.weight"].T        # [H, 4H]
            out[p + "mlp.dense_4h_to_h.weight"] = np.split(pj, tp, axis=1)[r]
            out[p + "mlp.dense_4h_to_h.bias"] = sd[hfp + "mlp.c_proj.bias"]
        out["transformer.final_layernorm.weight"] = sd["transformer.ln_f.weight"]
        out["transformer.final_layernorm.bias"] = sd["transformer.ln_f.bias"]
        shards.append(out)
    return shards


@pytest.mark.parametrize("version,tp", [(0, 2), (2.0, 2), (2.0, 1)])
def test_megatron_merge_matches_hf(version, tp):
    hf = _tiny_hf()
    shards = _megatron_shards(hf, tp=tp, version=version)
    cfg = gpt2.GPT2Config(vocab_size=V, max_seq_len=S, num_layers=L,
                          num_heads=NH, hidden_size=H)
    params = megatron_gpt.from_megatron_state_dicts(cfg, shards,
                                                    ckpt_version=version)
    ids = np.random.default_rng(0).integers(0, V, (2, 12)).astype(np.int32)
    ours = np.asarray(gpt2.forward(cfg, params, ids, train=False))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=2e-3)


def _nest_megatron(flat):
    """Re-nest a flat rank dict into the genuine Megatron layout:
    language_model.{embedding.{word,position}_embeddings.weight,
    transformer.layers...}."""
    lm = {"embedding": {"word_embeddings": {
              "weight": flat["word_embeddings.weight"]},
          "position_embeddings": {
              "weight": flat["position_embeddings.weight"]}},
          "transformer": {}}
    for k, v in flat.items():
        if k.startswith("transformer."):
            lm["transformer"][k[len("transformer."):]] = v
    return lm


def test_megatron_load_wrapper_nested(tmp_path):
    """torch-serialized Megatron wrapper dicts with the real nested
    embedding layout round-trip through load(), incl. inferred config."""
    hf = _tiny_hf()
    shards = _megatron_shards(hf, tp=1, version=2.0)
    f = tmp_path / "mp_rank_00_model_states.pt"
    torch.save({"model": {"language_model": _nest_megatron(shards[0])},
                "checkpoint_version": 2.0}, str(f))
    cfg = gpt2.GPT2Config(vocab_size=V, max_seq_len=S, num_layers=L,
                          num_heads=NH, hidden_size=H)
    spec, params = megatron_gpt.load([str(f)], cfg=cfg)
    ids = np.random.default_rng(1).integers(0, V, (2, 10)).astype(np.int32)
    ours = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=2e-3)


def test_megatron_inferred_config_multi_shard():
    """cfg=None with tp>1 must see the FULL vocab (not a shard slice)."""
    hf = _tiny_hf()
    shards = _megatron_shards(hf, tp=2, version=2.0)
    cfg = megatron_gpt.config_from_state_dicts(shards, num_heads=NH)
    assert cfg.vocab_size == V
    assert cfg.num_layers == L and cfg.hidden_size == H
    params = megatron_gpt.from_megatron_state_dicts(cfg, shards,
                                                    ckpt_version=2.0)
    ids = np.random.default_rng(2).integers(0, V, (2, 10)).astype(np.int32)
    ours = np.asarray(gpt2.forward(cfg, params, ids, train=False))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=2e-3)


def test_get_sd_loader_dispatch(tmp_path):
    """SDLoaderFactory analog: get_sd_loader('Megatron') returns a loader
    that merges shard files (reference state_dict_factory.py:42)."""
    from deepspeed_tpu.runtime.state_dict_factory import get_sd_loader

    hf = _tiny_hf()
    shards = _megatron_shards(hf, tp=1, version=2.0)
    f = tmp_path / "rank0.pt"
    torch.save({"model": {"language_model": shards[0]},
                "checkpoint_version": 2.0}, str(f))
    loader = get_sd_loader([str(f)], sd_type="Megatron")
    cfg = gpt2.GPT2Config(vocab_size=V, max_seq_len=S, num_layers=L,
                          num_heads=NH, hidden_size=H)
    spec, params = loader(cfg)
    ids = np.random.default_rng(3).integers(0, V, (1, 8)).astype(np.int32)
    ours = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=2e-3)

    with pytest.raises(ValueError):
        get_sd_loader([], sd_type="HF")
