"""Multi-replica serving: the incremental ServingEngine API
(submit/step/cancel/drain + streaming handles) and the ReplicaRouter
(prefix-affinity routing, blocks-in-use balancing, cross-replica KV
pull, drain/re-admit, supervisor integration).

Tier-1 (fast) coverage:
 - incremental API parity: submit+step-driven serving is token-identical
   to the batch ``serve()`` wrapper and to sequential ``generate``;
   handles stream exactly the committed tokens.
 - priorities / SLO classes order admission; preemption resumes still
   jump the queue.
 - ``cancel()``: queued requests drop immediately, active slots release
   their blocks at the iteration boundary with a ``cancelled`` timeline
   event — audited (``debug_checks=True`` throughout).
 - ``serve([])`` returns ``{}`` without tracing anything.
 - router routing units on jax-free fake replicas (affinity/hints/
   balance/drained), drain/re-admit handoff, supervisor grace ticks,
   and the router-state fault injections.
 - e2e: 2-replica affinity parity vs sequential, drained-replica
   KV-pull migration with zero prefix recompute (fp32 exact and kv8
   bit-exact vs an unmigrated kv8 twin), mid-flight drain with no
   dropped requests, per-replica compile budgets unchanged (strict
   sentry).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis.invariants import (PagedStateError,
                                               audit_router)
from deepspeed_tpu.inference.serving import (Request, RequestHandle,
                                             SLO_PRIORITY, ServingEngine,
                                             _PendingItem, _PendingQueue)
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.serving import ReplicaRouter, RouterSupervisor


# ---------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny():
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    spec = gpt2.build(cfg)
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        spec, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    return spec, cfg, engine


def _mk_engine(spec, params, **cfg_extra):
    config = {"dtype": "fp32", "tensor_parallel": {"tp_size": 1}}
    config.update(cfg_extra)
    return deepspeed_tpu.init_inference(spec, config=config, params=params)


_SRV_KW = dict(slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
               prefill_batch=2, debug_checks=True)


def _session_trace(cfg, n=9, sessions=3, seed=0, prefix_len=24,
                   max_new=10):
    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(0, cfg.vocab_size, prefix_len)
                for _ in range(sessions)]
    return prefixes, [
        Request(uid=i,
                prompt=np.concatenate(
                    [prefixes[i % sessions],
                     rng.integers(0, cfg.vocab_size,
                                  int(rng.integers(3, 8)))]),
                max_new_tokens=max_new)
        for i in range(n)]


def _sequential(engine, reqs):
    return {r.uid: engine.generate(r.prompt[None, :],
                                   max_new_tokens=r.max_new_tokens)[0]
            for r in reqs}


# ------------------------------------------------- incremental engine API
def test_pending_queue_priority_and_front():
    q = _PendingQueue()
    mk = lambda uid, pri: _PendingItem(req=Request(uid=uid, prompt=[1]),
                                       prior=[], priority=pri)
    q.push(mk("a", 0))
    q.push(mk("b", 2))
    q.push(mk("c", 0))
    q.push(mk("d", 2))
    assert [it.req.uid for it in q] == ["b", "d", "a", "c"]
    # preemption resume jumps every class
    q.push_front(mk("resume", 0))
    assert q[0].req.uid == "resume"
    # a later high-priority push still queues BEHIND the resume
    q.push(mk("e", 5))
    assert [it.req.uid for it in q][:2] == ["resume", "e"]
    assert q.remove("c").req.uid == "c" and q.remove("zz") is None
    assert len(q.drain()) == 5 and not q


def test_incremental_submit_step_matches_serve(tiny):
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg)
    seq = _sequential(engine, reqs)

    srv = ServingEngine(engine, **_SRV_KW)
    handles = [srv.submit(r) for r in reqs]
    assert all(h.status == "queued" for h in handles)
    while srv.step():
        pass
    for r, h in zip(reqs, handles):
        assert h.status == "finished"
        np.testing.assert_array_equal(h.result(timeout=0), seq[r.uid])
        # the stream is exactly the committed completion prefix
        toks = h.tokens()
        np.testing.assert_array_equal(
            np.asarray(toks, np.int32),
            seq[r.uid][len(r.prompt):len(r.prompt) + len(toks)])
        assert 1 <= len(toks) <= r.max_new_tokens
    # the batch wrapper over a fresh engine is identical
    srv2 = ServingEngine(engine, **_SRV_KW)
    outs = srv2.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(outs[r.uid], seq[r.uid])


def test_streaming_cursor_and_generated_tokens_counter(tiny):
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=2)
    srv = ServingEngine(engine, **_SRV_KW)
    h = srv.submit(reqs[0])
    got = []
    while not h.done or h.next_token(timeout=0) is not None:
        t = h.next_token(timeout=0)
        if t is None:
            if not srv.step() and h.done:
                break
        else:
            got.append(t)
    # drain any tail the loop's interleaving left unread
    while (t := h.next_token(timeout=0)) is not None:
        got.append(t)
    assert got == h.tokens()
    assert srv.stats()["generated_tokens"] == len(got)


def test_priority_and_slo_order_admission(tiny):
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=3)
    srv = ServingEngine(engine, **{**_SRV_KW, "slots": 1})
    log = []
    srv._admission_log = log
    low = srv.submit(Request(uid="low", prompt=reqs[0].prompt,
                             max_new_tokens=4), priority=0)
    slo = srv.submit(Request(uid="slo", prompt=reqs[1].prompt,
                             max_new_tokens=4), slo_class="interactive")
    high = srv.submit(Request(uid="high", prompt=reqs[2].prompt,
                              max_new_tokens=4), priority=9)
    assert slo.priority == SLO_PRIORITY["interactive"] == 1
    while srv.step():
        pass
    srv._admission_log = None
    assert [uid for uid, _ in log] == ["high", "slo", "low"]
    assert all(h.status == "finished" for h in (low, slo, high))


def test_cancel_pending_and_active(tiny):
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=4, max_new=20)
    srv = ServingEngine(engine, **{**_SRV_KW, "slots": 2})
    handles = [srv.submit(r) for r in reqs]
    # queued cancel (slots=2: request 3 cannot be admitted yet): immediate
    assert handles[3].cancel()
    assert handles[3].status == "cancelled"
    assert handles[3].result() is None
    srv.step()
    srv.step()
    # active cancel: lands at the next iteration boundary, frees blocks
    assert handles[0].status == "active"
    held_before = len(srv._held[0]) + len(srv._held[1])
    assert held_before > 0
    assert handles[0].cancel()
    assert handles[0].status == "active"   # not yet — boundary-deferred
    srv.step()                             # audit runs after the release
    assert handles[0].status == "cancelled"
    while srv.step():
        pass
    st = srv.stats()
    assert st["cancelled"] == 2
    assert handles[1].status == handles[2].status == "finished"
    names = [e["name"] for e in srv.timeline.events()]
    assert names.count("cancelled") == 2
    # unknown / finished uids refuse
    assert not srv.cancel("nope") and not handles[1].cancel()


def test_empty_serve_traces_nothing(tiny):
    spec, cfg, engine = tiny
    srv = ServingEngine(engine, **_SRV_KW)
    assert srv.serve([]) == {}
    assert srv.compile_count == 0 and srv.iterations == 0


def test_serve_on_busy_engine_raises(tiny):
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=2)
    srv = ServingEngine(engine, **_SRV_KW)
    srv.submit(reqs[0])
    with pytest.raises(RuntimeError, match="busy"):
        srv.serve([reqs[1]])
    while srv.step():
        pass


# ------------------------------------------------------- fake-replica units
class _FakeReplica:
    """Duck-typed stand-in for ServingEngine: enough surface for the
    router's routing/drain/audit logic, zero jax."""

    def __init__(self, block_size=8, depth_for=None):
        self.block_size = block_size
        self._host = None
        self._prefix = None
        self._pending = _PendingQueue()
        self._active = {}
        self._alloc = type("A", (), {"blocks_in_use": 0})()
        self.depth_for = depth_for or (lambda prompt: 0)
        self.prompt_tokens = 0
        self.prefix_hit_tokens = 0
        self.admitted = 0
        self.compile_count = 0
        self.compile_budget = 2
        self._c_gen_tokens = type("C", (), {"value": 0.0})()
        self.drained_calls = 0

    def affinity_probe(self, tokens):
        return {"device_blocks": self.depth_for(tokens), "host_blocks": 0,
                "blocks_in_use": self._alloc.blocks_in_use,
                "queue_depth": len(self._pending),
                "active": len(self._active)}

    def submit(self, request, priority=0, slo_class=None,
               eos_token_id=None):
        handle = RequestHandle(request, priority=priority,
                               slo_class=slo_class)
        self._pending.push(_PendingItem(req=request, prior=[],
                                        priority=priority,
                                        handle=handle))
        return handle

    def _submit_item(self, item, canceller=None):
        if item.handle is not None and canceller is not None:
            item.handle.set_canceller(canceller)
        self._pending.push(item)

    def step(self):
        if self._pending:
            item = self._pending.popleft()
            if item.handle is not None:
                item.handle._on_finish(np.asarray(item.req.prompt))
        return bool(self._pending)

    def cancel(self, uid):
        item = self._pending.remove(uid)
        if item is not None and item.handle is not None:
            item.handle._on_cancel()
        return item is not None

    def drain(self):
        self.drained_calls += 1
        return self._pending.drain()

    def warm_swap_programs(self):
        pass


def test_router_routing_units_affinity_balance_drained():
    # replica 1 "has" a 2-block prefix for prompts starting with 7
    deep = _FakeReplica(depth_for=lambda p: 2 if int(p[0]) == 7 else 0)
    flat = _FakeReplica()
    router = ReplicaRouter([flat, deep], kv_pull=False)
    h = router.submit(Request(uid="a", prompt=[7] * 20))
    assert router._handles["a"][1] == 1          # deepest hit wins
    assert router.stats()["routed_affinity"] == 1
    # no hit anywhere: balance by blocks_in_use
    flat._alloc.blocks_in_use = 50
    router.submit(Request(uid="b", prompt=[1] * 20))
    assert router._handles["b"][1] == 1
    assert router.stats()["routed_balance"] == 1
    # hint table co-locates a same-prefix request with NO resident state
    router2 = ReplicaRouter([_FakeReplica(), _FakeReplica()],
                            kv_pull=False)
    router2.submit(Request(uid="s0", prompt=[3] * 20))
    rid0 = router2._handles["s0"][1]
    router2.submit(Request(uid="s1", prompt=([3] * 17) + [9, 9, 9]))
    assert router2._handles["s1"][1] == rid0
    assert router2.stats()["routed_affinity"] == 1
    # drained replicas never route; draining the last live one raises
    router3 = ReplicaRouter([_FakeReplica(), _FakeReplica()],
                            policy="round_robin", kv_pull=False)
    router3.drain(0)
    for i in range(3):
        router3.submit(Request(uid=f"r{i}", prompt=[1] * 4))
        assert router3._handles[f"r{i}"][1] == 1
    with pytest.raises(RuntimeError, match="last live"):
        router3.drain(1)
    router3.readmit(0)
    router3.drain(1)                              # now legal


def test_router_drain_hands_off_and_supervisor_grace():
    a, b = _FakeReplica(), _FakeReplica()
    router = ReplicaRouter([a, b], policy="round_robin", kv_pull=False,
                           debug_checks=True)
    handles = [router.submit(Request(uid=i, prompt=[1] * 4))
               for i in range(4)]
    queued_on_a = len(a._pending)
    assert queued_on_a + len(b._pending) == 4
    handed = router.drain(0)
    assert handed == queued_on_a and a.drained_calls == 1
    assert len(b._pending) == 4                  # nothing dropped
    assert all(router._handles[h.uid][1] == 1 for h in handles)
    # cancel routes to the CURRENT owner after handoff
    assert router.cancel(handles[0].uid)
    assert handles[0].status == "cancelled"
    while router.step():
        pass
    assert all(h.done for h in handles)

    # supervisor: grace ticks hold a transient probe miss, expiry drains,
    # return re-admits (only replicas the supervisor itself drained)
    live = {0: 1, 1: 1}
    sup = RouterSupervisor(router, lambda: live, grace_ticks=1)
    router.readmit(0)
    assert sup.tick() == {"drained": [], "failed": [], "readmitted": []}
    live = {0: 1, 1: 0}                          # replica 1 goes dark
    assert sup.tick()["drained"] == []           # within grace
    assert sup.tick()["drained"] == [1]          # grace expired
    assert router.drained == [1]
    live = {0: 1, 1: 1}
    assert sup.tick()["readmitted"] == [1]
    assert router.drained == []
    # a manual drain is NOT the supervisor's to re-admit
    router.drain(0)
    assert sup.tick()["readmitted"] == []
    assert router.drained == [0]
    router.readmit(0)
    # stale-claim regression: supervisor drains a down replica, the
    # OPERATOR re-admits it while still down — the supervisor's claim
    # must die with that readmit, so a later operator drain (replica
    # live) is not auto-resurrected
    live = {0: 1, 1: 0}
    sup.tick()
    assert sup.tick()["drained"] == [1]
    router.readmit(1)                            # operator, while down
    live = {0: 1, 1: 1}                          # ...and it comes back
    sup.tick()                                   # claim must be dead now
    router.drain(1)                              # operator maintenance
    assert sup.tick()["readmitted"] == []
    assert router.drained == [1]
    router.readmit(1)


def test_supervisor_survives_fleet_wide_outage():
    """Every replica going dark must not crash the supervision loop: the
    last live replica stays in rotation (nowhere to hand its sessions),
    and recovery re-admits the ones that did drain."""
    router = ReplicaRouter([_FakeReplica(), _FakeReplica()],
                           kv_pull=False)
    live = {0: 0, 1: 0}
    sup = RouterSupervisor(router, lambda: live, grace_ticks=0)
    acts = sup.tick()                            # both dark, same tick
    assert len(acts["drained"]) == 1             # second refuses, no raise
    assert sup.tick()["drained"] == []           # keeps ticking calmly
    assert len(router.drained) == 1
    live = {0: 1, 1: 1}
    assert len(sup.tick()["readmitted"]) == 1
    assert router.drained == []


def test_threaded_worker_failure_rehomes_not_silence():
    """A replica whose step() raises must not die silently: the router
    pulls it out of routing, records the fault, and RE-HOMES its
    requests onto survivors (PR 15 crash protocol) so every caller gets
    a result — nobody blocks forever, nothing is dropped."""
    class _Exploding(_FakeReplica):
        def step(self):
            raise RuntimeError("boom")

    bad, good = _Exploding(), _FakeReplica()
    router = ReplicaRouter([bad, good], policy="round_robin",
                           kv_pull=False, threaded=True)
    handles = [router.submit(Request(uid=i, prompt=[1] * 4))
               for i in range(4)]
    router.start()
    try:
        for h in handles:
            h.result(timeout=10)                 # nobody blocks forever
    finally:
        router.stop()
    assert 0 in router.drained and 0 in router.failed
    assert 0 in router._worker_errors
    assert all(h.status == "finished" for h in handles)
    st = router.stats()
    assert st["replica_failures"] == 1
    assert st["requests_rehomed"] >= 1 and st["requests_failed"] == 0
    router.readmit(0)                            # operator says healthy
    assert 0 not in router._worker_errors and router.failed == []


def test_router_audit_fault_injection():
    a, b = _FakeReplica(), _FakeReplica()
    router = ReplicaRouter([a, b], kv_pull=False)
    h = router.submit(Request(uid="x", prompt=[1] * 4))
    audit_router(router)                         # green
    # same uid queued on two replicas
    b._pending.push(_PendingItem(req=Request(uid="x", prompt=[1] * 4),
                                 prior=[]))
    with pytest.raises(PagedStateError) as ei:
        audit_router(router)
    assert ei.value.invariant == "router-request-uniqueness"
    b._pending.drain()
    # a drained replica still holding work
    router._drained.add(0)
    if not a._pending:                           # x may live on b
        a._pending.push(_PendingItem(req=Request(uid="y", prompt=[1]),
                                     prior=[]))
    with pytest.raises(PagedStateError) as ei:
        audit_router(router)
    assert ei.value.invariant in ("router-drain-quiesced",
                                  "router-request-uniqueness")
    router._drained.discard(0)
    a._pending.drain()
    # a live handle no replica holds
    for rep in (a, b):
        rep._pending.drain()
    assert h.status == "queued"
    with pytest.raises(PagedStateError) as ei:
        audit_router(router)
    assert ei.value.invariant == "router-request-uniqueness"


def test_router_ctor_validation():
    with pytest.raises(ValueError, match="at least one"):
        ReplicaRouter([])
    with pytest.raises(ValueError, match="policy"):
        ReplicaRouter([_FakeReplica()], policy="nope")
    with pytest.raises(ValueError, match="block_size"):
        ReplicaRouter([_FakeReplica(block_size=8),
                       _FakeReplica(block_size=16)])


# --------------------------------------------------------------- router e2e
def test_router_two_replicas_parity_and_affinity(tiny):
    spec, cfg, engine = tiny
    prefixes, reqs = _session_trace(cfg)
    seq = _sequential(engine, reqs)
    srvs = [ServingEngine(_mk_engine(spec, engine.params), **_SRV_KW)
            for _ in range(2)]
    router = ReplicaRouter(srvs, debug_checks=True)
    outs = router.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(outs[r.uid], seq[r.uid],
                                      err_msg=f"uid {r.uid}")
    st = router.stats()
    # 3 sessions: at most one balance route per session, the rest follow
    # affinity (resident or hinted)
    assert st["routed_affinity"] >= len(reqs) - 3
    assert st["routed_balance"] <= 3
    # both replicas actually served traffic, budgets intact
    assert all(p["admitted"] > 0 for p in st["per_replica"])
    assert all(p["compile_count"] <= p["compile_budget"]
               for p in st["per_replica"])
    names = {e["name"] for e in router.timeline.events()}
    assert "route" in names


def _tiered_pair(spec, params, quantize=None):
    kw = dict(_SRV_KW, host_blocks=32, swap_batch=4)
    if quantize:
        kw["quantize"] = quantize
    return [ServingEngine(_mk_engine(spec, params), **kw)
            for _ in range(2)]


def test_kv_pull_migration_zero_recompute(tiny):
    """Acceptance: a drained replica's session resumes on a cold replica
    through the cross-replica KV pull with exact token parity and zero
    prefix recompute (only the mandatory sub-block tail prefills)."""
    spec, cfg, engine = tiny
    prefixes, reqs = _session_trace(cfg)
    seq = _sequential(engine, reqs)
    router = ReplicaRouter(_tiered_pair(spec, engine.params),
                           debug_checks=True)
    outs = router.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(outs[r.uid], seq[r.uid])
    # find session 0's home replica and drain it
    p0 = prefixes[0]
    probe = [rep.affinity_probe(np.concatenate([p0, [0]]))
             for rep in router.replicas]
    depth = [p["device_blocks"] + p["host_blocks"] for p in probe]
    rid0 = int(np.argmax(depth))
    assert depth[rid0] == len(p0) // 8           # whole prefix resident
    router.drain(rid0)
    tgt = router.replicas[1 - rid0]
    rng = np.random.default_rng(7)
    cont = Request(uid="cont",
                   prompt=np.concatenate(
                       [p0, rng.integers(0, cfg.vocab_size, 5)]),
                   max_new_tokens=8)
    seq_cont = engine.generate(cont.prompt[None, :], max_new_tokens=8)[0]
    pt0, ht0 = tgt.prompt_tokens, tgt.prefix_hit_tokens
    out = router.serve([cont])
    np.testing.assert_array_equal(out["cont"], seq_cont)
    st = router.stats()
    assert st["kv_pulls"] >= 1
    assert st["kv_pull_blocks"] >= len(p0) // 8
    # zero prefix recompute: the cold replica prefilled ONLY the tail
    # past the last pullable full block
    plen = len(cont.prompt)
    recompute = (tgt.prompt_tokens - pt0) - (tgt.prefix_hit_tokens - ht0)
    assert recompute == plen - ((plen - 1) // 8) * 8
    assert tgt.compile_count <= tgt.compile_budget
    names = {e["name"] for e in router.timeline.events()}
    assert {"drain", "kv_pull", "route"} <= names
    # re-admit: the drained replica serves again
    router.readmit(rid0)
    out2 = router.serve([Request(uid="back", prompt=reqs[0].prompt,
                                 max_new_tokens=6)])
    np.testing.assert_array_equal(
        out2["back"],
        engine.generate(reqs[0].prompt[None, :], max_new_tokens=6)[0])


def test_kv8_pull_bit_exact_vs_unmigrated(tiny):
    """kv8 composition: pulled int8 codes + scale rows are bit-identical,
    so a migrated kv8 session matches an UNMIGRATED kv8 engine exactly
    (same quantized model — deterministic codes)."""
    spec, cfg, engine = tiny
    prefixes, reqs = _session_trace(cfg, n=6)
    kw = dict(_SRV_KW, host_blocks=32, swap_batch=4, quantize="kv8")
    ref = ServingEngine(_mk_engine(spec, engine.params), **kw)
    ref_outs = ref.serve(reqs)

    router = ReplicaRouter(_tiered_pair(spec, engine.params,
                                        quantize="kv8"),
                           debug_checks=True)
    outs = router.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(outs[r.uid], ref_outs[r.uid])
    p0 = prefixes[0]
    depth = [rep.affinity_probe(np.concatenate([p0, [0]]))
             for rep in router.replicas]
    rid0 = int(np.argmax([d["device_blocks"] + d["host_blocks"]
                          for d in depth]))
    router.drain(rid0)
    rng = np.random.default_rng(11)
    cont = Request(uid="qcont",
                   prompt=np.concatenate(
                       [p0, rng.integers(0, cfg.vocab_size, 4)]),
                   max_new_tokens=6)
    ref_cont = ref.serve([cont])
    out = router.serve([Request(uid="qcont", prompt=cont.prompt,
                                max_new_tokens=6)])
    np.testing.assert_array_equal(out["qcont"], ref_cont["qcont"])
    assert router.stats()["kv_pulls"] >= 1


def test_drain_midflight_no_requests_dropped(tiny):
    """Drain while requests are queued AND decoding: everything finishes
    on the surviving replica, token-exact, on the original handles."""
    spec, cfg, engine = tiny
    prefixes, reqs = _session_trace(cfg, n=6, max_new=16)
    seq = _sequential(engine, reqs)
    router = ReplicaRouter(_tiered_pair(spec, engine.params),
                           debug_checks=True)
    handles = [router.submit(r) for r in reqs]
    for _ in range(3):
        router.step()
    victim = next(rid for rid in range(2)
                  if router.replicas[rid]._active or
                  router.replicas[rid]._pending)
    router.drain(victim)
    assert not router.replicas[victim]._active
    assert not router.replicas[victim]._pending
    while router.step():
        pass
    for r, h in zip(reqs, handles):
        assert h.status == "finished", (r.uid, h.status)
        np.testing.assert_array_equal(h.result(timeout=0), seq[r.uid],
                                      err_msg=f"uid {r.uid}")
    assert router.stats()["drains"] == 1


def test_threaded_router_smoke(tiny):
    """Worker-thread mode: same outputs, engines stepped only under
    their replica locks."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=4)
    seq = _sequential(engine, reqs)
    srvs = [ServingEngine(_mk_engine(spec, engine.params), **_SRV_KW)
            for _ in range(2)]
    router = ReplicaRouter(srvs, threaded=True)
    try:
        outs = router.serve(reqs)
    finally:
        router.stop()
    for r in reqs:
        np.testing.assert_array_equal(outs[r.uid], seq[r.uid])


def test_init_router_shares_weights(tiny):
    spec, cfg, _ = tiny
    deepspeed_tpu.comm.reset_topology()
    router = deepspeed_tpu.init_router(
        spec, config={"dtype": "fp32",
                      "tensor_parallel": {"tp_size": 1}},
        replicas=2, slots=2, max_seq_len=64, block_size=8,
        prefill_chunk=16, debug_checks=True)
    assert len(router.replicas) == 2
    p0 = router.replicas[0].engine.params
    p1 = router.replicas[1].engine.params
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(p0),
                    jax.tree_util.tree_leaves(p1)):
        assert a is b                      # one pytree, zero duplication
    rng = np.random.default_rng(3)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 12),
                    max_new_tokens=5) for i in range(3)]
    outs = router.serve(reqs)
    seq = _sequential(router.replicas[0].engine, reqs)
    for r in reqs:
        np.testing.assert_array_equal(outs[r.uid], seq[r.uid])
