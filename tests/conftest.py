"""Test harness: 8-device CPU-sim mesh.

The reference tests distributed behavior by spawning N processes over local GPUs
(``tests/unit/common.py DistributedExec``).  On TPU/JAX the equivalent — and
simpler — harness is a single process with 8 virtual CPU devices
(``--xla_force_host_platform_device_count``): every collective and sharding path
is exercised for real by XLA's CPU backend, no hardware needed (SURVEY §4).
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture(autouse=True)
def _reset_comm_state():
    """Each test gets a fresh module-level topology."""
    yield
    from deepspeed_tpu import comm

    comm.reset_topology()
    comm.comms_logger.reset()


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 CPU-sim devices, got {len(devs)}"
    return devs
