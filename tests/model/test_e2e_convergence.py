"""End-to-end convergence lane (reference
``tests/model/Megatron_GPT2/run_func_test.py``): a REAL byte-level-BPE
tokenizer trained on a synthetic corpus, a small GPT-2 trained through the
public engine to a target loss, checkpoint-resume mid-run, and a
perf/structural check of the headline bench entrypoint.

CPU-sim, marked slow; the real-hardware perf gate lives in bench.py (the
driver records BENCH_r{N}.json per round).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2

pytestmark = pytest.mark.slow


def _synthetic_corpus(n_sentences=400, seed=0):
    rng = np.random.default_rng(seed)
    subjects = ["the pipeline", "a tensor", "the optimizer", "our mesh",
                "the scheduler", "a kernel", "the compiler", "the runtime"]
    verbs = ["shards", "gathers", "reduces", "streams", "compiles",
             "fuses", "overlaps", "checkpoints"]
    objects = ["the gradients", "a layer", "the activations", "the weights",
               "every block", "the cache", "the batch", "the tokens"]
    lines = []
    for _ in range(n_sentences):
        lines.append(f"{rng.choice(subjects)} {rng.choice(verbs)} "
                     f"{rng.choice(objects)} .")
    return lines


def _train_tokenizer(lines, vocab_size=384):
    from tokenizers import ByteLevelBPETokenizer

    tok = ByteLevelBPETokenizer()
    tok.train_from_iterator(lines, vocab_size=vocab_size, min_frequency=1)
    return tok


def test_gpt2_converges_on_real_tokenized_corpus(tmp_path):
    lines = _synthetic_corpus()
    tok = _train_tokenizer(lines)
    vocab = tok.get_vocab_size()
    ids = [tok.encode(" ".join(lines[i:i + 4])).ids for i in range(0, 64, 4)]
    seq = 33
    data = np.stack([np.asarray((x * seq)[:seq], np.int32) for x in ids])

    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config(vocab_size=vocab, max_seq_len=seq, num_layers=2,
                          num_heads=2, hidden_size=64)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(cfg),
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                "zero_optimization": {"stage": 1},
                "steps_per_print": 1000})
    bs = engine.train_batch_size()
    rng = np.random.default_rng(0)

    losses = []
    for step in range(60):
        take = rng.integers(0, len(data), bs)
        _, m = engine.train_batch({"input_ids": data[take]})
        losses.append(float(m["loss"]))
        if step == 30:
            engine.save_checkpoint(str(tmp_path / "ck"))
    start = float(np.mean(losses[:3]))
    end = float(np.mean(losses[-3:]))
    # target-loss gate (reference run_func_test asserts a loss ceiling):
    # a 2-layer model must fit this 8-sentence corpus well below start
    assert end < start - 2.0, (start, end, losses[-5:])
    assert end < 2.5, losses[-5:]

    # checkpoint-resume continues the curve (no re-warmup spike)
    deepspeed_tpu.comm.reset_topology()
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(cfg),
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                "zero_optimization": {"stage": 1},
                "steps_per_print": 1000})
    engine2.load_checkpoint(str(tmp_path / "ck"))
    take = rng.integers(0, len(data), bs)
    _, m = engine2.train_batch({"input_ids": data[take]})
    assert float(m["loss"]) < start - 1.0  # resumed mid-curve, not fresh


def test_bench_entrypoint_smoke_and_contract():
    """The headline bench must emit its one-line JSON contract on the CPU
    smoke path (the driver runs the same file on real hardware; the
    recorded number is the perf-regression gate per round)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), os.pardir,
                                      os.pardir, "bench.py")],
        env=env, capture_output=True, text=True, timeout=1200)
    assert out.returncode == 0, out.stderr[-2000:]
    line = out.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert set(rec) == {"metric", "value", "unit", "vs_baseline"}
    assert rec["value"] > 0


def test_bench_perf_regression_floor():
    """On real hardware the headline bench must not regress below 0.90
    vs_baseline (round-3 recorded 1.07; the floor leaves chip-variance
    headroom).  The bench runs as a SUBPROCESS, which sees the real
    backend even though the test process is pinned to the CPU sim — the
    gate applies whenever that subprocess lands on a TPU, and the test
    skips on machines with none."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    # seconds-cheap backend probe before paying for the full bench
    probe = subprocess.run(
        [sys.executable, "-c", "import jax; print(jax.default_backend())"],
        env=env, capture_output=True, text=True, timeout=300)
    if "tpu" not in probe.stdout:
        pytest.skip(f"no TPU visible to subprocesses "
                    f"(backend={probe.stdout.strip()!r})")
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), os.pardir,
                                      os.pardir, "bench.py")],
        env=env, capture_output=True, text=True, timeout=1200)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["vs_baseline"] >= 0.90, rec
