"""Multi-process test worker: train tiny GPT-2 under a 2-device-per-process
mesh and dump per-step losses.  Launched by test_multiprocess.py with
``argv = pid nprocs port steps outfile [save_dir] [load_dir]`` (the
DistributedExec/DistributedFixture analog, reference tests/unit/common.py:71
and :202 — real cross-process collectives, no GPU; checkpoints written
under one world shape are resumed under another).
"""

import json
import os
import sys

if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=2")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax

jax.config.update("jax_platforms", "cpu")

pid, nprocs, port, steps = (int(sys.argv[1]), int(sys.argv[2]),
                            int(sys.argv[3]), int(sys.argv[4]))
outfile = sys.argv[5]
save_dir = sys.argv[6] if len(sys.argv) > 6 and sys.argv[6] != "-" else None
load_dir = sys.argv[7] if len(sys.argv) > 7 and sys.argv[7] != "-" else None

if nprocs > 1:
    jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                               num_processes=nprocs, process_id=pid)

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir, os.pardir))

import deepspeed_tpu
from deepspeed_tpu.models import gpt2

GLOBAL_BS = 4
mode = sys.argv[8] if len(sys.argv) > 8 and sys.argv[8] != "-" else "dense"

if mode == "stream":
    # ZeRO-Infinity param streaming: block params host-resident, host CPU
    # optimizer; exercises the multi-host grad-push combine
    config = {"train_micro_batch_size_per_gpu": 1,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "zero_optimization": {
                  "stage": 0,
                  "offload_optimizer": {"device": "cpu"},
                  "offload_param": {"device": "cpu"},
              },
              "steps_per_print": 100,
              "mesh": {}}
else:
    config = {"train_micro_batch_size_per_gpu": 1,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 2},
              "steps_per_print": 100,
              "mesh": {}}

engine, _, _, _ = deepspeed_tpu.initialize(
    model=gpt2.build(gpt2.GPT2Config.tiny()), config=config)
assert engine.train_batch_size() == GLOBAL_BS, engine.train_batch_size()

if load_dir:
    path, _ = engine.load_checkpoint(load_dir)
    assert path is not None, f"checkpoint load silently no-oped: {load_dir}"

rng = np.random.default_rng(0)  # same batches in every process
rows_per_proc = GLOBAL_BS // nprocs
losses = []
for _ in range(steps):
    full = rng.integers(0, 512, size=(GLOBAL_BS, 17)).astype(np.int32)
    local = full[pid * rows_per_proc:(pid + 1) * rows_per_proc]
    # multi-process contract (DeepSpeedDataLoader process_shard): each
    # controller passes its LOCAL rows, stacked [gas, local_rows, ...]
    _, m = engine.train_batch({"input_ids": local[None]})
    losses.append(float(m["loss"]))

if save_dir:
    engine.save_checkpoint(save_dir)

# exercise the host-level collective surface too
deepspeed_tpu.comm.barrier("test")
red = deepspeed_tpu.comm.host_all_reduce_sum([np.ones(3) * (pid + 1)])
with open(outfile, "w") as f:
    json.dump({"losses": losses, "host_sum": red[0].tolist(),
               "world": jax.device_count(),
               "procs": jax.process_count()}, f)
